"""Driver benchmark: prints ONE JSON line carrying the full metric set.

Primary metric (the ``metric``/``value``/``vs_baseline`` triple) mirrors
the reference's published blake3_64kb synthetic bench (3,517 MB/s,
README.md:309-319 / DESIGN.md:645-657): BLAKE3 hashing throughput over
64 KiB chunks, run *on device* (the Pallas kernel on TPU) because that's
where the gathered pool's integrity gate runs.

``extra`` carries the BASELINE.md north-star metrics ("Targets for the
TPU-native build"):

- ``pull_to_hbm``   — END-TO-END: a fixture GPT-2 checkpoint (~50 MB)
  pulled through the full CAS client from a loopback hub straight into
  device HBM (``pull --device=tpu`` path: chunk/hash/reconstruct/verify/
  land). ``time_to_hbm_s`` is the whole pull wall-clock; ``hbm_gbps`` is
  the host→HBM commit rate (models/loader.py _commit_stats).
- ``host_to_hbm``   — raw ``jax.device_put`` staging bandwidth, the
  upper bound for the commit stage.
- ``ici_all_gather``— pod-axis all-gather GB/s (only with >1 device;
  the driver's chip is single-device, the virtual-mesh CI job covers it).

Methodology note: the chip sits behind a tunnel, so naive host-side
timing measures the ~67 ms round-trip, not the device. The blake3 bench
chains iterations inside one dispatch and differences N-vs-1 wall-clocks
(details in bench_blake3_device's docstring); the other device benches
remain round-trip-inclusive and say so in their numbers.
"""

from __future__ import annotations

import json
import pathlib
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))

BASELINE_MBPS = 3517.0  # reference blake3_64kb, ReleaseFast x86_64
CHUNK = 64 * 1024
BATCH = 512
# Chained iterations inside one dispatch. Must be deep enough that the
# summed device time (~0.45 ms/iter) dwarfs the tunnel round-trip's
# +-tens-of-ms jitter, or the N-vs-1 differencing can even go negative.
ITERS = 513


def bench_blake3_device() -> dict:
    """Device-time measurement of the Pallas BLAKE3 kernel.

    Methodology (and why rounds 1-2 under-measured by ~8x): the chip is
    reached through a relay, so ANY host-side timing of individual
    dispatches measures the ~67 ms tunnel round-trip, not the kernel —
    and repeating an identical call can be served without re-execution,
    which over-measures instead. Neither artifact can touch this method:
    N hash iterations are CHAINED inside one jitted computation (each
    iteration's input is xor-perturbed by the previous digest, a real
    data dependency, so nothing can be elided), the wall-clock of N and
    of 1 iterations are differenced to cancel the single round-trip, and
    the digest is materialized on the host to force completion.

    Roofline: per 64-byte block, 7 rounds x 8 G x 22 u32 ops (6 add,
    4 xor, 4 rotates at shift+shift+or) on 4-lane state columns
    ~= 77 u32 ops/byte. A v5e VPU (8 sublanes x 128 lanes x 4 ALUs at
    ~0.94 GHz ~= 3.9 T u32 op/s) rooflines at ~50 GB/s for that count;
    the measured 60-68 GB/s implies the compiler folds part of the
    rotate/select traffic, i.e. the kernel saturates the VPU. HBM
    traffic (~1.05 B moved per B hashed) is two orders below the HBM
    roofline — compute-bound, as a hash should be.
    """
    import functools

    import jax
    import jax.numpy as jnp

    from zest_tpu.cas import hashing
    from zest_tpu.ops import best_hasher

    rng = np.random.default_rng(0)
    host = rng.integers(0, 256, size=(BATCH, CHUNK), dtype=np.uint8)
    words = jnp.asarray(host.view("<u4"))
    lengths = jnp.full((BATCH,), CHUNK, jnp.int32)
    hasher = best_hasher()

    # Correctness gate before timing: device digests must match the host
    # reference implementation bit-for-bit.
    got = np.asarray(hasher.hash_device(words, lengths))
    want = hashing.blake3_hash(host[0].tobytes())
    assert got[0].astype("<u4").tobytes() == want, "device BLAKE3 mismatch"

    if jax.default_backend() != "tpu":
        # No tunnel to cancel off-TPU, and the chained loop would grind
        # through interpret-mode Pallas — plain windowed timing of the
        # production hasher (the XLA lowering) is the right measure here.
        windows = []
        for _ in range(5):
            t0 = time.perf_counter()
            outs = [hasher.hash_device(words, lengths) for _ in range(8)]
            jax.block_until_ready(outs)
            windows.append((time.perf_counter() - t0) / 8)
        dt = sorted(windows)[len(windows) // 2]
        return {"mbps": round(BATCH * CHUNK / dt / 1e6, 1), "batch": BATCH,
                "method": "windowed-host-time"}

    @functools.partial(jax.jit, static_argnames=("n",))
    def chained(words, lengths, salt, n):
        def body(_i, acc):
            return hasher.hash_device(words ^ acc[0, 0] ^ salt, lengths)
        return jax.lax.fori_loop(
            0, n, body, jnp.zeros((words.shape[0], 8), jnp.uint32)
        )

    salt0 = jnp.uint32(0)
    np.asarray(chained(words, lengths, salt0, ITERS))  # compile + warm
    np.asarray(chained(words, lengths, salt0, 1))

    run = 0

    def wall(n: int) -> float:
        # Every timed dispatch gets a distinct salt: the chaining blocks
        # replay WITHIN a dispatch, the salt blocks it ACROSS repeats
        # (an identical repeated call can be served without re-executing).
        nonlocal run
        times = []
        for _ in range(5):
            run += 1
            t0 = time.perf_counter()
            np.asarray(chained(words, lengths, jnp.uint32(run), n))
            times.append(time.perf_counter() - t0)
        return min(times)

    t_n, t_1 = wall(ITERS), wall(1)
    dt = (t_n - t_1) / (ITERS - 1)
    assert dt > 0, (
        f"round-trip jitter swamped the measurement (t_{ITERS}={t_n:.3f}s "
        f"<= t_1={t_1:.3f}s); raise ITERS"
    )
    return {
        "mbps": round(BATCH * CHUNK / dt / 1e6, 1),
        "batch": BATCH,
        "chained_iters": ITERS,
        "roundtrip_ms": round(t_1 * 1e3, 1),
        "method": "chained-device-time",
    }


def bench_pull_to_hbm() -> dict:
    """End-to-end: loopback hub → CAS client → verified cache → HBM.

    Variance note: the fixture hub, the CAS client, this interpreter,
    and the chip relay all share one vCPU here, so wall-clock swings
    several-fold run to run (observed 1.4-36s for identical work) —
    treat the number as an existence proof of the pipeline, not a
    stable figure. The primary blake3 metric is immune (differencing
    cancels environment noise); the landing stage alone is ~0.8s
    (warm 0.2 + decode 0.2 + one batched commit 0.6, measured idle)."""
    from tests.fixtures import FixtureHub, FixtureRepo, gpt2_checkpoint_files
    from zest_tpu.config import Config
    from zest_tpu.transfer.pull import pull_model

    files = gpt2_checkpoint_files(n_embd=512, n_layer=4)
    total = sum(len(b) for b in files.values())
    repo = FixtureRepo("bench/gpt2-50mb", files, chunks_per_xorb=64)
    with FixtureHub(repo) as hub, tempfile.TemporaryDirectory() as root:
        rootp = pathlib.Path(root)
        cfg = Config(hf_home=rootp / "hf", cache_dir=rootp / "zest",
                     hf_token="hf_test", endpoint=hub.url)
        t0 = time.perf_counter()
        res = pull_model(cfg, "bench/gpt2-50mb", device="tpu", no_p2p=True)
        dt = time.perf_counter() - t0
        hbm = res.stats.get("hbm") or {}
        if "error" in hbm:
            raise RuntimeError(f"HBM commit failed: {hbm['error']}")
        out = {
            "time_to_hbm_s": round(dt, 3),
            "checkpoint_bytes": total,
            "pull_gbps": round(total / dt / 1e9, 3),
            "hbm_gbps": hbm.get("gbps"),
            "hbm_tensors": hbm.get("tensors"),
            "direct": hbm.get("direct"),
        }
        res.params = None  # release HBM
        return out


def bench_decode(steps: int = 64) -> dict:
    """KV-cached decode throughput (serving path): a tiny random-init
    Llama decodes ``steps`` tokens inside one jitted scan; tok/s from the
    min warm wall-clock (whole-scan dispatch, so the relay round-trip is
    amortized across all steps)."""
    import jax
    import jax.numpy as jnp

    from zest_tpu.models import llama

    cfg = llama.LlamaConfig.tiny(n_ctx=steps + 8, n_embd=256, n_layer=4,
                                 n_head=8, n_kv_head=4, d_ff=512)
    params = llama.init_params(jax.random.key(0), cfg, dtype=jnp.bfloat16)
    base = jnp.asarray(list(range(1, 9)), jnp.int32)

    # Salt every timed repeat via the first prompt token — an identical
    # repeated dispatch can be served without re-execution on the relay
    # (same countermeasure as the primary blake3 bench's salt).
    @jax.jit
    def fn(p, first):
        prompt = base.at[0].set(first)
        return llama.generate_cached(p, cfg, prompt, steps)

    np.asarray(fn(params, jnp.int32(0)))  # compile + warm
    times = []
    for i in range(1, 4):
        t0 = time.perf_counter()
        np.asarray(fn(params, jnp.int32(i)))
        times.append(time.perf_counter() - t0)
    dt = min(times)
    return {"tok_s": round((steps + base.shape[0]) / dt, 1),
            "steps": steps, "wall_s": round(dt, 3),
            "model": "llama-tiny-4L-256d-bf16"}


def bench_host_to_hbm(mbytes: int = 256) -> dict:
    import jax

    x = np.zeros(mbytes * 1024 * 1024, dtype=np.uint8)
    jax.device_put(x[: 1024 * 1024]).block_until_ready()  # warm path
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_put(x).block_until_ready()
        times.append(time.perf_counter() - t0)
    dt = sorted(times)[len(times) // 2]
    return {"gbps": round(len(x) / dt / 1e9, 3), "mbytes": mbytes}


def bench_ici_all_gather() -> dict | None:
    import jax

    if len(jax.devices()) < 2:
        return None  # single-chip driver; the virtual-mesh CI job covers it
    from zest_tpu.bench_suite import bench_ici_all_gather as suite_bench

    r = suite_bench()
    return {"gbps": round(r.mb_per_s / 1e3, 3)}  # mb_per_s is a property


def main() -> None:
    import jax

    blake3 = bench_blake3_device()
    # The extras are far more moving parts (loopback hub, CAS client,
    # loader); a failure there must not cost the primary metric or the
    # one-JSON-line contract.
    extra = {}
    import os

    extras = [
        ("pull_to_hbm", bench_pull_to_hbm),
        ("host_to_hbm", bench_host_to_hbm),
        ("ici_all_gather", bench_ici_all_gather),
    ]
    if os.environ.get("ZEST_BENCH_DECODE") == "1":
        # Opt-in: the nested decode scan compiles for many minutes on a
        # relay-attached chip — too slow for the driver's bench budget.
        extras.insert(2, ("decode", bench_decode))
    for name, fn in extras:
        try:
            result = fn()
        except Exception as exc:
            result = {"error": f"{type(exc).__name__}: {exc}"}
        if result is not None:
            extra[name] = result

    print(json.dumps({
        "metric": "blake3_64kb_device",
        "value": blake3["mbps"],
        "unit": "MB/s",
        "vs_baseline": round(blake3["mbps"] / BASELINE_MBPS, 3),
        "device": jax.devices()[0].platform,
        "batch": blake3["batch"],
        "extra": extra,
    }))


if __name__ == "__main__":
    main()
